"""Shape / layout manipulation ops (python/paddle/tensor/manipulation.py parity)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

builtins_slice = builtins.slice

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ._helpers import nondiff_op, unwrap
from ..core.dtype import int64 as _i64

__all__ = [
    "reshape",
    "reshape_",
    "flatten",
    "unflatten",
    "squeeze",
    "unsqueeze",
    "transpose",
    "moveaxis",
    "swapaxes",
    "concat",
    "stack",
    "unstack",
    "split",
    "chunk",
    "tensor_split",
    "tile",
    "expand",
    "expand_as",
    "broadcast_to",
    "broadcast_tensors",
    "flip",
    "rot90",
    "roll",
    "gather",
    "gather_nd",
    "scatter",
    "scatter_nd_add",
    "scatter_nd",
    "index_select",
    "index_sample",
    "index_add",
    "index_put",
    "take_along_axis",
    "put_along_axis",
    "slice",
    "strided_slice",
    "crop",
    "pad",
    "unbind",
    "repeat_interleave",
    "as_strided",
    "view",
    "view_as",
    "unfold",
    "masked_fill",
    "where",
    "numel",
    "shard_index",
    "cast",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    return tuple(int(unwrap(s)) for s in shape)


def reshape(x, shape, name=None):
    s = _shape_arg(shape)
    return apply_op(lambda v: jnp.reshape(v, s), x, op_name="reshape")


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._value = out._value
    x._node = out._node
    x._out_idx = out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def impl(v):
        nd = v.ndim
        a = start_axis % nd if nd else 0
        b = stop_axis % nd if nd else 0
        new_shape = v.shape[:a] + (-1,) + v.shape[b + 1:]
        return jnp.reshape(v, new_shape)

    return apply_op(impl, x, op_name="flatten")


def unflatten(x, axis, shape, name=None):
    from ._helpers import unwrap as _uw

    shape = tuple(int(_uw(s)) for s in shape)

    def impl(v):
        ax = axis % v.ndim
        return jnp.reshape(v, v.shape[:ax] + shape + v.shape[ax + 1:])

    return apply_op(impl, x, op_name="unflatten")


def squeeze(x, axis=None, name=None):
    ax = axis
    if isinstance(ax, (list, tuple)):
        ax = tuple(int(a) for a in ax)
    elif ax is not None:
        ax = int(ax)

    def impl(v):
        if ax is None:
            return jnp.squeeze(v)
        axes = ax if isinstance(ax, tuple) else (ax,)
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return apply_op(impl, x, op_name="squeeze")


def unsqueeze(x, axis, name=None):
    ax = axis
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(int(a) for a in ax)
    else:
        ax = (int(ax),)
    return apply_op(lambda v: jnp.expand_dims(v, ax), x, op_name="unsqueeze")


def transpose(x, perm=None, name=None):
    p = None if perm is None else tuple(int(a) for a in perm)
    return apply_op(lambda v: jnp.transpose(v, p), x, op_name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply_op(
        lambda v: jnp.moveaxis(v, source, destination), x, op_name="moveaxis"
    )


def swapaxes(x, axis1, axis2, name=None):
    return apply_op(
        lambda v: jnp.swapaxes(v, int(axis1), int(axis2)), x, op_name="swapaxes"
    )


def concat(x, axis=0, name=None):
    ax = int(unwrap(axis))
    tensors = list(x)
    return apply_op(
        lambda *vs: jnp.concatenate(vs, axis=ax), *tensors, op_name="concat"
    )


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_op(
        lambda *vs: jnp.stack(vs, axis=int(axis)), *tensors, op_name="stack"
    )


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else jnp.shape(unwrap(x))[axis]
    outs = apply_op(
        lambda v: tuple(
            jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis)
        ),
        x,
        op_name="unstack",
    )
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(unwrap(axis))
    v = unwrap(x)
    dim = jnp.shape(v)[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {dim} along axis {ax} is not evenly "
                f"divisible by {num_or_sections}"
            )
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(unwrap(s)) for s in num_or_sections]
        neg = [i for i, s in enumerate(sections) if s < 0]
        if neg:
            known = sum(s for s in sections if s >= 0)
            sections[neg[0]] = dim - known
    offsets = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        offsets.append(acc)
    outs = apply_op(
        lambda u: tuple(jnp.split(u, offsets, axis=ax)), x, op_name="split"
    )
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    v = unwrap(x)
    outs = apply_op(
        lambda u: tuple(jnp.array_split(u, num_or_indices, axis=int(axis))),
        x,
        op_name="tensor_split",
    )
    return list(outs)


def tile(x, repeat_times, name=None):
    reps = tuple(int(unwrap(r)) for r in repeat_times)
    return apply_op(lambda v: jnp.tile(v, reps), x, op_name="tile")


def expand(x, shape, name=None):
    s = _shape_arg(shape)

    def impl(v):
        tgt = tuple(
            v.shape[i - (len(s) - v.ndim)] if d == -1 else d for i, d in enumerate(s)
        )
        return jnp.broadcast_to(v, tgt)

    return apply_op(impl, x, op_name="expand")


def expand_as(x, y, name=None):
    tgt = tuple(jnp.shape(unwrap(y)))
    return apply_op(lambda v: jnp.broadcast_to(v, tgt), x, op_name="expand_as")


def broadcast_to(x, shape, name=None):
    s = _shape_arg(shape)
    return apply_op(lambda v: jnp.broadcast_to(v, s), x, op_name="broadcast_to")


def broadcast_tensors(inputs, name=None):
    outs = apply_op(
        lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *inputs, op_name="broadcast"
    )
    return list(outs)


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return apply_op(lambda v: jnp.flip(v, axis=ax), x, op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x, op_name="rot90")


def roll(x, shifts, axis=None, name=None):
    return apply_op(
        lambda v: jnp.roll(v, shifts, axis=axis), x, op_name="roll"
    )


def gather(x, index, axis=0, name=None):
    ax = int(unwrap(axis))
    return apply_op(
        lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=ax),
        x,
        index,
        op_name="gather",
    )


def gather_nd(x, index, name=None):
    def impl(v, idx):
        nd = idx.shape[-1]
        return v[tuple(jnp.moveaxis(idx, -1, 0))] if nd == v.ndim else v[
            tuple(jnp.moveaxis(idx, -1, 0))
        ]

    return apply_op(impl, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def impl(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        z = v.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)

    return apply_op(impl, x, index, updates, op_name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def impl(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(impl, x, index, updates, op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    s = _shape_arg(shape)

    def impl(i, u):
        z = jnp.zeros(s, jnp.result_type(u))
        return z.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(impl, index, updates, op_name="scatter_nd")


def index_select(x, index, axis=0, name=None):
    return apply_op(
        lambda v, i: jnp.take(v, i, axis=int(axis)), x, index, op_name="index_select"
    )


def index_sample(x, index, name=None):
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i, axis=1), x, index, op_name="index_sample"
    )


def index_add(x, index, axis, value, name=None):
    def impl(v, i, u):
        # builtins.slice — this module's own `slice` op shadows it
        import builtins

        idx = [builtins.slice(None)] * v.ndim
        idx[axis] = i
        return v.at[tuple(idx)].add(u)

    return apply_op(impl, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def impl(v, u, *idx):
        if accumulate:
            return v.at[tuple(idx)].add(u)
        return v.at[tuple(idx)].set(u)

    return apply_op(impl, x, value, *indices, op_name="index_put")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i, axis=int(axis)),
        arr,
        indices,
        op_name="take_along_axis",
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def impl(v, i, u):
        u = jnp.broadcast_to(u, i.shape) if jnp.ndim(u) else jnp.full(i.shape, u, v.dtype)
        dims = list(range(v.ndim))
        dims.remove(axis % v.ndim)
        full_idx = jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij")
        full_idx[axis % v.ndim] = i
        if reduce == "add":
            return v.at[tuple(full_idx)].add(u)
        if reduce == "multiply" or reduce == "mul":
            return v.at[tuple(full_idx)].multiply(u)
        return v.at[tuple(full_idx)].set(u)

    return apply_op(impl, arr, indices, values, op_name="put_along_axis")


def slice(input, axes, starts, ends, name=None):
    axes = [int(unwrap(a)) for a in axes]
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]

    def impl(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins_slice(s, e)
        return v[tuple(idx)]

    return apply_op(impl, input, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes = [int(unwrap(a)) for a in axes]
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]
    strides = [int(unwrap(s)) for s in strides]

    def impl(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(s, e, st)
        return v[tuple(idx)]

    return apply_op(impl, x, op_name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    s = _shape_arg(shape)
    offs = [0] * len(s) if offsets is None else [int(unwrap(o)) for o in offsets]

    def impl(v):
        idx = tuple(
            builtins_slice(o, o + (d if d != -1 else v.shape[i] - o))
            for i, (o, d) in enumerate(zip(offs, s))
        )
        return v[idx]

    return apply_op(impl, x, op_name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    p = [int(unwrap(v)) for v in pad]

    def impl(v):
        nd = v.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle semantics: pad applies to last len(p)//2 spatial dims,
            # ordered (last_dim_lo, last_dim_hi, ..., per data_format)
            width = [(0, 0)] * nd
            npairs = len(p) // 2
            if data_format.endswith("HWC") or data_format in ("NLC", "NDHWC", "NHWC"):
                spatial = list(range(1, 1 + npairs))
            else:
                spatial = list(range(nd - npairs, nd))
            for k, axis_i in enumerate(spatial):
                width[axis_i] = (p[2 * k], p[2 * k + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, width, mode="constant", constant_values=value)
        return jnp.pad(v, width, mode=jmode)

    return apply_op(impl, x, op_name="pad")


def unbind(input, axis=0, name=None):
    return unstack(input, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = unwrap(repeats)
    return apply_op(
        lambda v: jnp.repeat(v, r, axis=axis), x, op_name="repeat_interleave"
    )


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError(
        "as_strided has no XLA equivalent; use reshape/slice/unfold"
    )


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    # dtype view = bit reinterpretation (reference view semantics), not a cast
    from ..core.dtype import convert_dtype

    d = convert_dtype(shape_or_dtype)
    return apply_op(
        lambda v: jax.lax.bitcast_convert_type(v, d), x, op_name="view_dtype"
    )


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def unfold(x, axis, size, step, name=None):
    def impl(v):
        dim = v.shape[axis]
        n = (dim - size) // step + 1
        starts = jnp.arange(n) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]
        out = jnp.take(v, idx.reshape(-1), axis=axis)
        shp = list(v.shape)
        shp[axis:axis + 1] = [n, size]
        out = out.reshape(shp)
        return jnp.moveaxis(out, axis + 1, -1)

    return apply_op(impl, x, op_name="unfold")


def masked_fill(x, mask, value, name=None):
    return apply_op(
        lambda v, m, val: jnp.where(m, val.astype(v.dtype) if hasattr(val, "astype") else val, v),
        x,
        mask,
        value,
        op_name="masked_fill",
    )


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero

        return nonzero(condition, as_tuple=True)
    return apply_op(
        lambda c, a, b: jnp.where(c, a, b), condition, x, y, op_name="where"
    )


def numel(x, name=None):
    return Tensor(jnp.asarray(jnp.size(unwrap(x)), _i64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Vocab-shard index remap (reference: fluid ops shard_index, used by
    c_embedding / VocabParallelEmbedding)."""
    def impl(i):
        shard_size = (index_num + nshards - 1) // nshards
        lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
        in_range = (i >= lo) & (i < hi)
        return jnp.where(in_range, i - lo, ignore_value)

    return nondiff_op(impl, "shard_index")(input)


def cast(x, dtype):
    from ..core import dtype as dtypes

    d = dtypes.convert_dtype(dtype)
    return apply_op(lambda v: v.astype(d), x, op_name="cast")


# ---- round-2 long tail (reference python/paddle/tensor/manipulation.py) ----


def take(x, index, mode="raise", name=None):
    """Flat-index gather (manipulation.py take): treats x as 1-D."""
    def f(v, i):
        n = jnp.size(v)
        if mode == "wrap":
            i = ((i % n) + n) % n
        elif mode == "clip":
            i = jnp.clip(i, 0, n - 1)
        return jnp.take(v.reshape(-1), i)

    return apply_op(f, x, index, op_name="take")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
        x, op_name="diagonal")


def reverse(x, axis, name=None):
    """Legacy alias of flip (manipulation.py reverse)."""
    return flip(x, axis)


def vsplit(x, num_or_sections, name=None):
    """Split along axis 0 for >=2-D tensors (manipulation.py vsplit)."""
    return split(x, num_or_sections, axis=0)


def as_complex(x, name=None):
    """[..., 2] real → complex (manipulation.py as_complex)."""
    return apply_op(
        lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x,
        op_name="as_complex")


def as_real(x, name=None):
    """complex → [..., 2] real (manipulation.py as_real)."""
    return apply_op(
        lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x,
        op_name="as_real")


def broadcast_shape(x_shape, y_shape):
    """Pure shape computation (manipulation.py broadcast_shape)."""
    import numpy as _np

    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def rank(input, name=None):
    """0-D int tensor holding ndim (manipulation.py rank)."""
    return Tensor(jnp.asarray(unwrap(input).ndim, _i64))


def shape(input, name=None):
    """1-D int tensor holding the shape (the reference returns a tensor so
    shapes compose into graphs; under tracing these are static anyway)."""
    return Tensor(jnp.asarray(unwrap(input).shape, _i64))


for _n in ("take", "diagonal", "reverse", "vsplit", "as_complex", "as_real",
           "broadcast_shape", "rank", "shape"):
    __all__.append(_n)


def index_fill(x, index, axis, value, name=None):
    """Fill entries along ``axis`` at positions ``index`` with the scalar
    ``value`` (reference tensor/manipulation.py index_fill)."""
    def impl(v, i, *maybe_val):
        val = maybe_val[0] if maybe_val else value
        moved = jnp.moveaxis(v, int(axis), 0)
        fill = jnp.broadcast_to(jnp.asarray(val, v.dtype),
                                (i.shape[0],) + moved.shape[1:])
        out = moved.at[i].set(fill)
        return jnp.moveaxis(out, 0, int(axis))

    from ..core.tensor import Tensor as _T

    if isinstance(value, _T):
        return apply_op(impl, x, index, value, op_name="index_fill")
    return apply_op(impl, x, index, op_name="index_fill")


def index_fill_(x, index, axis, value, name=None):
    out = index_fill(x, index, axis, value)
    x.set_value(out.value if hasattr(out, "value") else out)
    return x


__all__.extend(["index_fill", "index_fill_"])
