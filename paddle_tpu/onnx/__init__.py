"""paddle.onnx parity (reference: python/paddle/onnx/export.py — delegates
to paddle2onnx).

TPU-native: the portable AOT serving format of this framework is StableHLO
(`jax.export`, see `paddle_tpu.inference`); ``export`` emits that artifact
(``<path>.stablehlo`` + ``<path>.pdiparams``) so the call site keeps
working, and notes that true .onnx emission needs the (unbundled)
paddle2onnx/onnx toolchain.
"""
from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Reference export.py:24. Emits the framework's AOT artifact; raises
    only if the model cannot be traced/exported at all."""
    if input_spec is None:
        raise ValueError(
            "export requires input_spec (a list of paddle_tpu.static."
            "InputSpec) to trace the model")
    try:
        import onnx  # noqa: F401
        has_onnx = True
    except ImportError:
        has_onnx = False
    if not has_onnx:
        warnings.warn(
            "onnx/paddle2onnx are not bundled in this TPU image; exporting "
            "the StableHLO AOT artifact instead (loadable via "
            "paddle_tpu.inference.create_predictor). Convert to .onnx on a "
            "machine with paddle2onnx installed.", stacklevel=2)
    from .. import jit

    jit.save(layer, path, input_spec=input_spec)
    return path
