"""paddle.onnx parity (reference: python/paddle/onnx/export.py — delegates
to paddle2onnx).

TPU-native: the portable AOT serving format of this framework is StableHLO
(`jax.export`, see `paddle_tpu.inference`); ``export`` emits that artifact
(``<path>.stablehlo`` + ``<path>.pdiparams``) so the call site keeps
working, and notes that true .onnx emission needs the (unbundled)
paddle2onnx/onnx toolchain.
"""
from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Reference export.py:24. Emits the framework's AOT artifact; raises
    only if the model cannot be traced/exported at all."""
    if input_spec is None:
        raise ValueError(
            "export requires input_spec (a list of paddle_tpu.static."
            "InputSpec) to trace the model")
    # this build NEVER emits .onnx (paddle2onnx operates on Paddle program
    # protos, which this framework does not produce) — warn every time so
    # nobody ships a .stablehlo thinking it's ONNX
    warnings.warn(
        "paddle_tpu.onnx.export emits the StableHLO AOT artifact "
        "(<path>.stablehlo + <path>.pdiparams, loadable via paddle_tpu."
        "inference.create_predictor), NOT a .onnx file; ONNX conversion "
        "requires the paddle2onnx toolchain operating on reference program "
        "protos.", stacklevel=2)
    from .. import jit

    jit.save(layer, path, input_spec=input_spec)
    return path
